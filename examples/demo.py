#!/usr/bin/env python
"""End-to-end tour of dlaf_tpu (runs on 1 TPU chip or a CPU mesh).

CPU mesh: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
              python examples/demo.py
"""
import numpy as np

import dlaf_tpu as dt
import dlaf_tpu.testing as tu
from dlaf_tpu.matrix import io as mio
from dlaf_tpu.scalapack import api as sl

n, nb = 512, 64
grid = dt.Grid.create()  # all visible devices, most-square Pr x Pc
print(f"grid: {grid}")

a = tu.random_hermitian_pd(n, np.float32, seed=0)
b = tu.random_hermitian_pd(n, np.float32, seed=1)

# --- factor + solve -----------------------------------------------------------
mat_b = dt.DistributedMatrix.from_global(grid, b, (nb, nb))
fac = dt.cholesky_factorization("L", mat_b)  # in-place: mat_b holds L
rhs = dt.DistributedMatrix.from_global(grid, tu.random_matrix(n, 4, np.float32, 2), (nb, nb))
x = dt.triangular_solver("Left", "L", "N", "N", 1.0, fac, rhs)
print("trsm residual:", np.abs(np.tril(fac.to_global()) @ x.to_global()).max() > 0)

# --- generalized eigenproblem -------------------------------------------------
mat_a = dt.DistributedMatrix.from_global(grid, np.tril(a), (nb, nb))
mat_b2 = dt.DistributedMatrix.from_global(grid, np.tril(b), (nb, nb))
res = dt.hermitian_generalized_eigensolver("L", mat_a, mat_b2, spectrum=(0, 9))
print("10 smallest generalized eigenvalues:", np.round(res.eigenvalues, 4))

# --- ScaLAPACK-style surface --------------------------------------------------
ctx = sl.create_grid(*grid.grid_size)
desc = sl.Descriptor(n, n, nb, nb)
w, z = sl.pheevd(ctx, "L", np.tril(a), desc)
print("pheevd smallest eigenvalue:", round(float(w[0]), 4))
sl.free_grid(ctx)

# --- mixed precision: f32 compute, f64 accuracy -------------------------------
import jax

jax.config.update("jax_enable_x64", True)
a64 = tu.random_hermitian_pd(n, np.float64, seed=3)
b64 = tu.random_matrix(n, 4, np.float64, seed=4)
xs, info = dt.positive_definite_solver_mixed(
    "L",
    dt.DistributedMatrix.from_global(grid, np.tril(a64), (nb, nb)),
    dt.DistributedMatrix.from_global(grid, b64, (nb, nb)),
)
print(
    f"mixed posv: {info.iters} refinement sweeps, backward error "
    f"{info.backward_error:.1e} (f32 factorization, f64 result)"
)
eres, einfo = dt.hermitian_eigensolver_mixed(
    "L", dt.DistributedMatrix.from_global(grid, np.tril(a64), (nb, nb))
)
print(
    f"mixed heev: ortho error {einfo.ortho_error:.1e} after "
    f"{einfo.iters} sweeps (f32 pipeline, f64 eigenpairs)"
)
pres, pinfo = dt.hermitian_eigensolver_mixed(
    "L", dt.DistributedMatrix.from_global(grid, np.tril(a64), (nb, nb)),
    spectrum=(0, 31),
)
print(
    f"mixed partial heev (32 smallest): residual {pinfo.residual:.1e} "
    f"after {pinfo.iters} sweeps — target-precision work is O(n^2 k)"
)

# --- distributed-buffer ScaLAPACK mode (per-rank local slabs) -----------------
desc64 = sl.make_desc(n, n, nb, nb)
local = sl.global_to_local(np.tril(a64), desc64, grid)  # this process's slabs
fac_slabs = sl.ppotrf_local("L", local, desc64, grid)
print(
    f"local-buffer ppotrf: {len(fac_slabs)} rank slab(s) held by this "
    "process, no global buffer assembled (on a multi-process world each "
    "process passes only its own slabs — see docs/MIGRATION.md)"
)

# --- IO -----------------------------------------------------------------------
mio.save("/tmp/demo_matrix.npz", fac)
back = mio.load("/tmp/demo_matrix.npz", grid)
print("io round-trip exact:", np.array_equal(back.to_global(), fac.to_global()))
